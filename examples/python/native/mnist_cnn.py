"""MNIST CNN (reference: examples/python/native/mnist_cnn.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import build_mnist_cnn

from _util import get_config, train_and_report
from accuracy import ModelAccuracy


def main():
    # 8 epochs: the >=90% gate (accuracy.py:19-24 role) must hold on the
    # no-egress SYNTHETIC fallback dataset too, which converges slower than
    # real MNIST (measured: 87.1% @3 epochs, 90.6% @8; real MNIST clears
    # the gate well before this)
    config = get_config(batch_size=64, epochs=8)
    from flexflow_tpu.keras.datasets import mnist

    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)

    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 1, 28, 28])
    build_mnist_cnn(model, inp)
    train_and_report(
        model, [x_train], y_train, config, "mnist_cnn",
        optimizer=ff.AdamOptimizer(model, alpha=1e-3),
        target_accuracy=ModelAccuracy.MNIST_CNN.value,
    )


if __name__ == "__main__":
    main()
