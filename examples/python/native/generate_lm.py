"""KV-cache autoregressive generation on a causal transformer LM
(serving/generate.py — the incremental-decoding role of the reference's
Triton prototype)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.serving.generate import GenerativeSession

from _util import get_config


def main():
    config = get_config(batch_size=2, epochs=1)
    vocab, hidden, heads, window = 100, 64, 4, 24
    model = ff.FFModel(config)
    tokens = model.create_tensor([config.batch_size, window],
                                 ff.DataType.DT_INT32)
    t = model.embedding(tokens, vocab, hidden, ff.AggrMode.AGGR_MODE_NONE,
                        name="emb")
    for i in range(2):
        attn = model.multihead_attention(t, t, t, hidden, heads, causal=True,
                                         name=f"l{i}_attn")
        t = model.layer_norm(model.add(t, attn), [-1], name=f"l{i}_ln1")
        h = model.dense(t, hidden * 2, ff.ActiMode.AC_MODE_GELU,
                        name=f"l{i}_ff1")
        t = model.layer_norm(model.add(t, model.dense(h, hidden,
                                                      name=f"l{i}_ff2")),
                             [-1], name=f"l{i}_ln2")
    model.softmax(model.dense(t, vocab, name="lm_head"))
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    prompt = np.random.RandomState(0).randint(
        1, vocab, size=(config.batch_size, 6)).astype(np.int32)
    session = GenerativeSession(model, max_len=window)
    out = session.generate(prompt, max_new_tokens=10)
    print("prompt:", prompt.tolist())
    print("greedy:", out.tolist())
    # chunked dispatch (K decode steps per jitted scan — the serving
    # latency lever) + top-k sampling; same seed => same tokens at any K
    sampled = session.generate(prompt, max_new_tokens=10,
                               tokens_per_dispatch=5, temperature=0.8,
                               top_k=20, seed=1)
    print("sampled (top-k 20, T=0.8, K=5):", sampled.tolist())


if __name__ == "__main__":
    main()
