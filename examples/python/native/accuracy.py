"""Accuracy gates for the native examples (reference:
examples/python/native/accuracy.py:19-24 — ModelAccuracy enum with a ≥90%
CI threshold per model)."""
import enum


class ModelAccuracy(enum.Enum):
    MNIST_MLP = 90.0
    MNIST_CNN = 90.0
    REUTERS_MLP = 90.0
    CIFAR10_CNN = 90.0
    CIFAR10_ALEXNET = 90.0
