"""GPipe pipeline parallelism through the PCG (new capability — the
reference's OP_PIPELINE is an unused enum).

`compile(parallel_axes={"stage": S})` maps the transformer's repeated-block
body onto GPipe stages (parallel/pipeline_plan.py): each device holds its
stages' weights, microbatches flow over neighbor ICI links, and reverse-mode
AD of the scan is the backward pipeline. Composes with data parallelism
(dp x stage mesh below). The low-level kernel demo lives in
flexflow_tpu/models/pipeline_transformer.py; this example is the USER path.
"""
import numpy as np

import _bootstrap  # noqa: F401

import jax

import flexflow_tpu as ff
from flexflow_tpu.models import TransformerConfig, build_bert_encoder


def main():
    n_dev = len(jax.devices())
    stages = min(4, n_dev)
    dp = max(1, n_dev // stages)

    config = ff.FFConfig()
    config.num_devices = dp * stages
    config.batch_size = 8
    config.pipeline_microbatches = 4
    model = ff.FFModel(config)
    tokens = model.create_tensor([8, 12], ff.DataType.DT_INT32)
    cfg = TransformerConfig(hidden_size=32, embedding_size=32, num_heads=4,
                            num_layers=stages, sequence_length=12,
                            vocab_size=64)
    build_bert_encoder(model, tokens, cfg)
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=5e-3),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
        parallel_axes=({"data": dp, "stage": stages} if dp > 1
                       else {"stage": stages}),
    )
    plan = model.executor.pipeline_plan
    print(f"pipeline plan: {plan.n_stages} stages x {plan.segs_per_stage} "
          f"block(s)/stage over {len(plan.region_guids)} ops "
          f"(dp={dp}, microbatches={config.pipeline_microbatches})")

    rng = np.random.RandomState(0)
    x = rng.randint(0, 64, (8, 12)).astype(np.int32)
    y = (x % 2).astype(np.int32)[..., None]
    for epoch, h in enumerate(model.fit(x, y, epochs=10, verbose=False)):
        if epoch % 2 == 0:
            print(f"epoch {epoch}: loss {h['loss']:.4f} "
                  f"acc {h['accuracy']:.2f}")


if __name__ == "__main__":
    main()
