"""GPipe pipeline parallelism over a 'stage' mesh axis (new capability —
the reference's OP_PIPELINE is an unused enum; kernels/pipeline.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from flexflow_tpu.models.pipeline_transformer import (
    init_pipeline_params,
    make_train_step,
)


def main():
    stages = min(4, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:stages]), ("stage",))
    vocab, hidden, heads, layers = 64, 32, 4, stages * 2
    params = init_pipeline_params(jax.random.PRNGKey(0), layers, hidden,
                                  heads, stages=stages)
    emb = jax.random.normal(jax.random.PRNGKey(1), (vocab, hidden)) * 0.02
    head = jax.random.normal(jax.random.PRNGKey(2), (hidden, vocab)) * 0.02
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, (8, 12)))
    labels = jnp.asarray(rng.randint(0, vocab, (8, 12)))

    step = make_train_step(mesh, microbatches=4, lr=0.1)
    for it in range(10):
        params, emb, head, loss = step(params, emb, head, tokens, labels)
        if it % 2 == 0:
            print(f"iter {it}: loss {float(loss):.4f} "
                  f"({stages} pipeline stages)")


if __name__ == "__main__":
    main()
