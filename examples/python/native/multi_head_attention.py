"""Standalone multi-head attention demo (reference:
examples/python/native/multi_head_attention.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=16, epochs=1)
    batch, seq, d = config.batch_size, 32, 64
    n = batch * 4
    rng = np.random.RandomState(0)
    q = rng.randn(n, seq, d).astype(np.float32)
    y = rng.randint(0, 2, size=(n, seq, 1)).astype(np.int32)

    model = ff.FFModel(config)
    qt = model.create_tensor([batch, seq, d])
    t = model.multihead_attention(qt, qt, qt, d, 8)
    t = model.dense(t, 2)
    model.softmax(t)
    train_and_report(model, [q], y, config, "multi_head_attention")


if __name__ == "__main__":
    main()
