"""AlexNet (reference: examples/python/native/alexnet.py,
bootcamp_demo/ff_alexnet_cifar10.py — CIFAR-10 upsampled to 229x229)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import build_alexnet

from _util import get_config, synthetic_images, train_and_report


def main():
    config = get_config(batch_size=64, epochs=1)
    size = 229
    x, y = synthetic_images(config.batch_size * 4, 3, size)

    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 3, size, size])
    build_alexnet(model, inp)
    train_and_report(model, [x], y, config, "alexnet")


if __name__ == "__main__":
    main()
