"""XDL CTR model (reference: examples/cpp/XDL)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import XDLConfig, build_xdl

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=64, epochs=1)
    cfg = XDLConfig(embedding_size=[100000] * 4)
    batch = config.batch_size
    n = batch * 8
    rng = np.random.RandomState(0)
    sparse_np = [rng.randint(0, v, size=(n, 1)).astype(np.int32)
                 for v in cfg.embedding_size]
    y = rng.randint(0, 2, size=(n, 1)).astype(np.int32)

    model = ff.FFModel(config)
    sparse = [model.create_tensor([batch, 1], ff.DataType.DT_INT32)
              for _ in cfg.embedding_size]
    build_xdl(model, sparse, cfg)
    train_and_report(model, sparse_np, y, config, "xdl")


if __name__ == "__main__":
    main()
