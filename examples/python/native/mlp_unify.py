"""MLP_Unify (reference: examples/cpp/MLP_Unify/mlp.cc) — the minimal
Unity-search demo: run with --search-budget > 0 to let the strategy search
choose per-op parallelization over the mesh."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import build_mlp_unify

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=64, epochs=1)
    batch, in_dim = config.batch_size, 1024
    n = batch * 8
    rng = np.random.RandomState(0)
    x1 = rng.randn(n, in_dim).astype(np.float32)
    x2 = rng.randn(n, in_dim).astype(np.float32)
    y = rng.randint(0, 10, size=(n, 1)).astype(np.int32)

    model = ff.FFModel(config)
    in1 = model.create_tensor([batch, in_dim])
    in2 = model.create_tensor([batch, in_dim])
    build_mlp_unify(model, in1, in2, hidden_dims=(4096, 4096, 4096, 10))
    train_and_report(model, [x1, x2], y, config, "mlp_unify")


if __name__ == "__main__":
    main()
