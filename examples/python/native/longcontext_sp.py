"""Long-context training with sequence/context parallelism.

Shards a transformer's position dim over a 'seq' mesh axis; attention runs
the ring kernel (K/V blocks rotating on neighbor ICI links) or the Ulysses
all-to-all variant (--ulysses). With --search, the Unity search chooses the
parallelization itself under --enable-sequence-parallel.

Run on the CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python longcontext_sp.py [--ulysses | --search]
"""
import sys

import _bootstrap  # noqa: F401

import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.ffconst import ActiMode

from _util import get_config, train_and_report


def main():
    ulysses = "--ulysses" in sys.argv
    searched = "--search" in sys.argv
    for flag in ("--ulysses", "--search"):
        if flag in sys.argv:
            sys.argv.remove(flag)

    import jax

    n_dev = jax.device_count()
    sp = min(4, n_dev)
    batch, seq, hidden, heads = 2, 64 * sp, 64, sp

    config = get_config(batch_size=batch, epochs=2)
    if searched:
        config.enable_sequence_parallel = True
        config.search_budget = max(config.search_budget, 8)
        config.use_native_search = False

    model = ff.FFModel(config)
    tokens = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    t = model.embedding(tokens, 1000, hidden, ff.AggrMode.AGGR_MODE_NONE,
                        name="emb")
    for i in range(2):
        attn = model.multihead_attention(
            t, t, t, hidden, heads,
            sequence_parallel=not searched,
            sequence_parallel_mode="ulysses" if ulysses else "ring",
            name=f"l{i}_attn")
        t = model.layer_norm(model.add(t, attn), [-1], name=f"l{i}_ln1")
        h = model.dense(t, hidden * 4, ActiMode.AC_MODE_GELU, name=f"l{i}_ff1")
        t = model.layer_norm(model.add(t, model.dense(h, hidden,
                                                      name=f"l{i}_ff2")),
                             [-1], name=f"l{i}_ln2")
    model.softmax(model.dense(t, 4, name="cls"))

    rng = np.random.RandomState(0)
    x = rng.randint(0, 1000, size=(batch, seq)).astype(np.int32)
    y = (x[..., None] % 4).astype(np.int32)

    kwargs = {} if searched else {"parallel_axes": {"seq": sp}}
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=1e-3),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
        **kwargs,
    )
    mode = ("searched" if searched
            else "ulysses" if ulysses else "ring")
    print(f"[longcontext_sp] mode={mode} seq={seq} devices={n_dev} "
          f"axes={model.search_result.mesh_axes if searched else {'seq': sp}}")
    hist = model.fit([x], y, batch_size=batch, epochs=config.epochs)
    print(f"[longcontext_sp] loss {hist[0]['loss']:.4f} -> "
          f"{hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    main()
