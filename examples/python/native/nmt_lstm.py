"""LSTM NMT (reference capability: nmt/ legacy app) on synthetic copy task."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import build_lstm_nmt

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=32, epochs=2)
    batch, seq, vocab = config.batch_size, 24, 1000
    n = batch * 8
    rng = np.random.RandomState(0)
    src = rng.randint(0, vocab, size=(n, seq)).astype(np.int32)
    tgt = src.copy()  # copy task
    y = src[..., None].astype(np.int32)

    model = ff.FFModel(config)
    src_t = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    tgt_t = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    build_lstm_nmt(model, src_t, tgt_t, src_vocab=vocab, tgt_vocab=vocab,
                   embed_dim=128, hidden_size=256)
    train_and_report(model, [src, tgt], y, config, "nmt_lstm",
                     optimizer=ff.AdamOptimizer(model, alpha=1e-3))


if __name__ == "__main__":
    main()
