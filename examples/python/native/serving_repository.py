"""Serving model repository + metrics walk-through (reference role: the
Triton prototype's model-repository UX). Builds a model-spec repository on
disk, loads it onto an InferenceServer, serves over HTTP, and reads the
Prometheus metrics endpoint."""
import json
import os
import tempfile
import urllib.request

import _bootstrap  # noqa: F401

import numpy as np

from flexflow_tpu.serving import InferenceServer, ModelRepository


def main():
    repo_dir = tempfile.mkdtemp(prefix="ff_repo_")
    mdir = os.path.join(repo_dir, "mlp")
    os.makedirs(mdir)
    spec = {
        "format": "flexflow_tpu_c_model",
        "config": {"batch_size": 8},
        "ops": [
            {"type": "input", "name": "x", "dims": [8, 16],
             "dtype": "float32", "inputs": [], "outputs": [1]},
            {"type": "dense", "name": "fc1", "inputs": [1], "outputs": [2],
             "params": {"out_dim": 32, "activation": "relu"}},
            {"type": "dense", "name": "fc2", "inputs": [2], "outputs": [3],
             "params": {"out_dim": 4}},
            {"type": "softmax", "name": "sm", "inputs": [3], "outputs": [4],
             "params": {}},
        ],
    }
    with open(os.path.join(mdir, "model_spec.json"), "w") as f:
        json.dump(spec, f)
    with open(os.path.join(mdir, "config.json"), "w") as f:
        json.dump({"format": "ff_cspec", "file": "model_spec.json",
                   "max_batch_size": 8}, f)

    server = InferenceServer()
    repo = ModelRepository(repo_dir)
    print("repository models:", repo.model_names())
    print("loaded:", repo.load(server))

    httpd = server.serve_http(port=0)  # ephemeral port
    port = httpd.server_address[1]
    x = np.random.RandomState(0).randn(3, 16).astype(np.float32)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v2/models/mlp/infer",
        data=json.dumps({"inputs": {"x": x.tolist()}}).encode(),
        headers={"Content-Type": "application/json"},
    )
    out = json.loads(urllib.request.urlopen(req, timeout=30).read())
    print("http infer output shape:",
          np.asarray(out["outputs"]).shape)
    metrics = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    print("metrics:\n" + metrics.strip())
    httpd.shutdown()
    server.shutdown()


if __name__ == "__main__":
    main()
