"""Split/concat round-trip (reference: examples/python/native/split.py,
examples/cpp/split_test)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=32, epochs=1)
    batch, d = config.batch_size, 64
    n = batch * 4
    rng = np.random.RandomState(0)
    x = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, 10, size=(n, 1)).astype(np.int32)

    model = ff.FFModel(config)
    inp = model.create_tensor([batch, d])
    a, b = model.split(inp, [d // 2, d // 2], axis=1)
    a = model.dense(a, 32, ff.ActiMode.AC_MODE_RELU)
    b = model.dense(b, 32, ff.ActiMode.AC_MODE_RELU)
    t = model.concat([a, b], axis=1)
    t = model.dense(t, 10)
    model.softmax(t)
    train_and_report(model, [x], y, config, "split")


if __name__ == "__main__":
    main()
