"""MNIST MLP (reference: examples/python/native/mnist_mlp.py) with the ≥90%
accuracy gate. Uses the keras-frontend mnist dataset (synthetic fallback
when no dataset file is available)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import build_mnist_mlp

from _util import get_config, train_and_report
from accuracy import ModelAccuracy


def main():
    config = get_config(batch_size=64, epochs=5)
    from flexflow_tpu.keras.datasets import mnist

    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)

    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 784])
    build_mnist_mlp(model, inp)
    train_and_report(
        model, [x_train], y_train, config, "mnist_mlp",
        optimizer=ff.AdamOptimizer(model, alpha=1e-3),
        target_accuracy=ModelAccuracy.MNIST_MLP.value,
    )


if __name__ == "__main__":
    main()
